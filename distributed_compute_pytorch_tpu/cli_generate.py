"""``dcp-generate`` — sample tokens from a trained causal-LM checkpoint.

The inference-side companion of ``dcp-train`` (the reference repo trains
only; ``/root/reference/main.py`` has no generation path). The framework
carries no tokenizer (the reference has none either), so prompts and
outputs are token-id sequences — the contract every tokenizer-owning
caller can script against:

    dcp-generate --ckpt_path ck.npz --model gpt2 --model_preset tiny \\
        --prompt 12,7,90 --max_new_tokens 16 --temperature 0.8

Prints one JSON line: {"prompt": [...], "tokens": [...], "new": [...]}.
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_prompt(s: str) -> list[int]:
    try:
        ids = [int(t) for t in s.replace(",", " ").split()]
    except ValueError:
        raise SystemExit(f"--prompt must be token ids, got {s!r}")
    if not ids:
        raise SystemExit("--prompt is empty")
    return ids


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--ckpt_path", required=True,
                   help="checkpoint written by dcp-train (v1 file or "
                        "sharded v2 directory)")
    p.add_argument("--model", default="gpt2", choices=("gpt2", "llama"),
                   help="causal families only (BERT is bidirectional)")
    p.add_argument("--model_preset", default=None)
    p.add_argument("--vocab_size", type=int, default=None)
    p.add_argument("--max_seq_len", type=int, default=None)
    p.add_argument("--prompt", required=True,
                   help="comma/space-separated token ids")
    p.add_argument("--max_new_tokens", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top_k", type=int, default=None,
                   help="sample only among the k highest-probability "
                        "tokens (temperature > 0)")
    p.add_argument("--top_p", type=float, default=None,
                   help="nucleus sampling: smallest token set with "
                        "cumulative probability >= p (temperature > 0)")
    p.add_argument("--eos_id", type=int, default=None,
                   help="stop a row at this token id (output is trimmed "
                        "at the first occurrence)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--force-cpu", action="store_true", dest="force_cpu")
    args = p.parse_args(argv)

    if args.force_cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from distributed_compute_pytorch_tpu.infer import generate
    from distributed_compute_pytorch_tpu.models.registry import build_model
    from distributed_compute_pytorch_tpu.train.checkpoint import (
        restore_params)

    kw = {k: v for k, v in (("preset", args.model_preset),
                            ("vocab_size", args.vocab_size),
                            ("max_seq_len", args.max_seq_len))
          if v is not None}
    model = build_model(args.model, **kw)
    template, _ = model.init(jax.random.key(0))
    params = restore_params(args.ckpt_path, template)

    ids = _parse_prompt(args.prompt)
    vocab = model.config.vocab_size
    bad = [t for t in ids if not 0 <= t < vocab]
    if bad:
        # the embedding gather would CLAMP out-of-range ids silently
        raise SystemExit(f"prompt ids {bad} outside vocab [0, {vocab})")
    if args.eos_id is not None and not 0 <= args.eos_id < vocab:
        # an unreachable eos would silently never stop anything
        raise SystemExit(f"--eos_id {args.eos_id} outside vocab [0, {vocab})")
    if args.temperature == 0.0 and (args.top_k is not None
                                    or args.top_p is not None):
        # greedy ignores truncation; silence here would mislead
        raise SystemExit("--top_k/--top_p need --temperature > 0 "
                         "(sampling); temperature 0 is greedy")
    prompt = jnp.asarray(ids, jnp.int32)[None, :]
    out = generate(model, params, prompt, args.max_new_tokens,
                   temperature=args.temperature, eos_id=args.eos_id,
                   top_k=args.top_k, top_p=args.top_p,
                   rng=jax.random.key(args.seed))
    toks = [int(t) for t in out[0]]
    new = toks[len(ids):]
    if args.eos_id is not None and args.eos_id in new:
        new = new[:new.index(args.eos_id) + 1]
    print(json.dumps({"prompt": ids, "tokens": toks[:len(ids)] + new,
                      "new": new}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
