"""Replica-set serving: a health-checked router over N batcher replicas.

PRs 5-9 made one ``ContinuousBatcher`` a sound, fully instrumented
failure domain — deadlines, shed, drain, token-identical session
reconstruction, SLO histograms, flight recorder. But one batcher is
still one queue and one point of failure; the north star (heavy
traffic from millions of users) needs the failure domain to be *one
replica of N*. :class:`ServeRouter` owns N independent
``ContinuousBatcher`` replicas (each its own compiled programs, block
pool and radix cache — typically each its own mesh on real hardware)
and turns a replica death into a migration instead of an outage.

Dispatch — SLO-aware least-loaded with radix affinity:

- Every routing decision probes each healthy replica's prefix cache
  with the READ-ONLY ``prefix_match_len`` probe
  (``RadixCache.longest_match_len``: no LRU touch, no refcounts — a
  probe that mutated LRU order would let routing evict state the loser
  replicas still want). The replica holding the longest cached prefix
  of the request's prompt wins, because a cache hit skips that much
  prefill — cache hit rate is a CLUSTER property once there is more
  than one pool. With the hierarchical KV tier enabled (kv_tier.py:
  ``--host_cache_mb`` / ``--disk_cache_dir``, each replica owning its
  own host pool) the probe counts HOST/DISK-demoted prefixes as warm
  too: promoting spilled bytes back to device is one H2D copy, far
  cheaper than re-prefilling the prefix on a cold replica.
- Affinity yields to load: each candidate's backlog is estimated in
  ticks (unshared prefill suffix + segment-rounded decode budget of
  everything already assigned this round, scaled by the replica's
  observed mean TPOT from ``stats_snapshot()``), and a warm replica
  more than ``affinity_max_extra_ticks`` ahead of the least-loaded one
  loses the request anyway — bounded queueing skew is worth more than
  a warm prefix (DESIGN.md carries the tradeoff).

Robustness — health, breaker, migration:

- Health per replica: heartbeat recency (each replica's scheduler
  thread beats ``on_heartbeat`` between device calls; the router
  timestamps every beat) and consecutive-fault counters feed a
  :class:`CircuitBreaker` per replica: CLOSED -> OPEN on
  ``fault_threshold`` consecutive faults, OPEN -> HALF_OPEN when the
  deterministic exponential-backoff schedule (``elastic.
  backoff_delays``, jitter-seeded per replica) says to probe,
  HALF_OPEN -> CLOSED on a successful canary / back to OPEN on
  failure, and DEAD once the probe budget is exhausted (only an
  explicit :meth:`ServeRouter.probe_replica` revives it).
- A replica death is observed, never raised: ``serve_detailed`` never
  raises, so a replica that faulted past its own ``max_recoveries``
  budget returns its live rows as ``failed`` with the ``"device lost
  after ..."`` marker (plus anything still queued). The router treats
  that as the failover trigger: every such session is MIGRATED — the
  PR 5 reconstruction argument applied ACROSS replicas. The sampling
  key for a row's t-th token is ``fold_in(key(seed), n_logical + t)``
  — a pure function of (seed, tokens-known-so-far) — so re-admitting
  ``prompt + generated-so-far`` on a DIFFERENT replica with the same
  explicit seed continues the identical token stream (greedy is
  trivially identical). The router materialises ``seed=None`` to the
  request's global index up front, exactly the single-batcher default,
  so placement and migration never change any sampled stream.
- A continuation whose ``prompt + partial`` outgrows the target
  replica's prompt window falls back to FULL REPLAY from the original
  prompt — same seed, so still token-identical, just recomputed.
- Deadline-aware re-shedding: when capacity shrinks, a migrated
  request replays with only its REMAINING wall budget; one already
  past its deadline at failover time is finalised ``timeout`` (with
  its partial tokens) or ``shed`` (queued, nothing generated) instead
  of wasting survivor capacity.
- Heartbeat-staleness takeover (opt-in ``heartbeat_stale_s``): a
  replica wedged so hard its scheduler thread stops beating — and has
  no tick watchdog of its own to convert the hang into a device-lost
  — is declared dead mid-round; its whole assignment replays on the
  survivors and the zombie thread's eventual output is discarded.
- Graceful degradation is policy: with k of N replicas open/dead the
  partitioner simply spreads over the survivors at reduced goodput,
  and with ZERO healthy replicas requests fail fast with a structured
  error instead of wedging. A cluster-wide drain is one SIGTERM: the
  same ``PreemptionGuard`` object is passed to every replica, each
  finishes its in-flight rows and sheds its queue, and the router does
  not re-place the shed work.

Every failover dumps the flight ring (``reason="replica_failover"``)
naming the dead replica and the migrated sessions; all events a
replica records are tagged with its index via ``flight.replica_tag``
wrapped around each worker thread.

Elastic membership (ISSUE 20, driven by ``serve_fleet.
ElasticFleetController``): the replica set is no longer fixed at
construction. :meth:`ServeRouter.add_replica` appends a warm member
(scale-up, or the replacement for a breaker-DEAD one);
:meth:`ServeRouter.retire_replica` removes one — mid-round it drains
that single replica through a per-replica latch ORed into its drain
object, and the cut sessions re-enter the next round on survivors
exactly like a failover, minus the fault. Indices are stable (a
retired slot goes quiet, never reused), RETIRED is terminal to the
probe machinery (``probe_replica`` refuses; only the controller's
``readmit_replica`` — the upgrade walk's re-admission — returns one),
and a transiently mixed-``weights_version`` fleet is legal: handoffs
only target same-version replicas, token replay covers the rest.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace

from distributed_compute_pytorch_tpu.obs import flight
from distributed_compute_pytorch_tpu.obs.tracing import instant
from distributed_compute_pytorch_tpu.serve import Request
from distributed_compute_pytorch_tpu.serve_lifecycle import (
    CANCELLED, FAILED, OK, SHED, TIMEOUT, RequestResult)
from distributed_compute_pytorch_tpu.train.elastic import (
    backoff_delays, retry_with_backoff)

# serve.handle_fault's recovery-budget-exhausted marker: the substring
# that classifies a failed result as "this replica is gone" (migrate)
# vs. a per-request failure (terminal)
DEVICE_LOST_MARKER = "device lost after"

# breaker states
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
DEAD = "dead"
# membership state (ISSUE 20): a RETIRED replica has been removed from
# the fleet on purpose — scale-down, replacement of a DEAD member, or
# the drain step of a rolling weight upgrade. Terminal for probing:
# record_ok/record_fault/probe_replica all refuse to flip it (the
# replacement already holds its traffic), only the controller's
# explicit readmit_replica (the upgrade walk's re-admission) does.
RETIRED = "retired"


class CircuitBreaker:
    """Per-replica dispatch gate with deterministic backoff.

    CLOSED admits traffic. ``fault_threshold`` consecutive faults trip
    it OPEN with a retry time from the ``elastic.backoff_delays``
    schedule (explicit ``jitter_seed`` — N replicas seeded ``seed + i``
    desynchronise their probes reproducibly). When the retry time
    arrives the router takes the single HALF_OPEN probe slot
    (:meth:`begin_probe`); the canary's outcome either re-CLOSEs the
    breaker or re-OPENs it with the next (longer) delay. Exhausting
    the ``probe_budget`` schedule leaves the breaker DEAD: the router
    never auto-probes it again, only an explicit
    ``ServeRouter.probe_replica`` (an operator action) can revive it.
    """

    def __init__(self, *, fault_threshold: int = 1, probe_budget: int = 4,
                 probe_base_delay_s: float = 0.25, jitter_seed: int = 0):
        if fault_threshold < 1:
            raise ValueError(f"fault_threshold must be >= 1, got "
                             f"{fault_threshold}")
        self.fault_threshold = fault_threshold
        self.delays = backoff_delays(probe_budget, probe_base_delay_s,
                                     jitter_seed)
        self.state = CLOSED
        self.consecutive = 0      # consecutive observed faults
        self.trips = 0            # times the breaker opened
        self.retry_at: float | None = None
        self._k = 0               # next backoff-schedule index

    @property
    def healthy(self) -> bool:
        return self.state == CLOSED

    def record_ok(self) -> None:
        if self.state == RETIRED:
            return            # membership is the controller's call
        self.consecutive = 0
        self._k = 0
        self.retry_at = None
        self.state = CLOSED

    def record_fault(self, now: float) -> None:
        if self.state == RETIRED:
            return            # already out of the fleet
        self.consecutive += 1
        if self.state == HALF_OPEN or self.consecutive >= self.fault_threshold:
            self.trips += 1
            if self._k < len(self.delays):
                self.retry_at = now + self.delays[self._k]
                self._k += 1
                self.state = OPEN
            else:
                self.retry_at = None
                self.state = DEAD

    def probe_due(self, now: float) -> bool:
        return (self.state == OPEN and self.retry_at is not None
                and now >= self.retry_at)

    def begin_probe(self) -> None:
        self.state = HALF_OPEN


@dataclass
class _Session:
    """Router-side host state for one routed request: everything needed
    to replay it token-identically on another replica, plus the
    metadata accumulated across placements."""

    req: Request                       # original, seed materialised
    arrive_abs: float                  # absolute arrival instant
    deadline_at: float | None          # absolute deadline (None = none)
    tokens: list = field(default_factory=list)   # generated so far
    # "prefill" until the prompt has been prefilled somewhere; with a
    # prefill tier configured, such sessions are placed on prefill
    # replicas and hop to the decode tier right after their first token
    phase: str = "decode"
    migrated: int = 0
    rounds: int = 0                    # placements attempted
    ticks: int = 0
    recoveries: int = 0
    cached_prefix: int = 0
    queue_wait_s: float | None = None
    ttft_s: float | None = None


class _ReplicaDrain:
    """The drain object each worker hands its replica: the OR of the
    cluster-wide latch and that replica's retirement flag (ISSUE 20).
    A retirement mid-round looks, to the one replica, exactly like a
    SIGTERM drain — admission stops, in-flight rows finish, the queue
    sheds — but the ROUTER re-places the cut sessions on survivors
    instead of finalising them, because only this member is leaving."""

    def __init__(self, router: "ServeRouter", i: int, drain):
        self._router, self._i, self._drain = router, i, drain

    @property
    def preempted(self) -> bool:
        return bool(self._router._retiring[self._i]
                    or (self._drain is not None
                        and getattr(self._drain, "preempted", False)))


class ServeRouter:
    """Thread-based router over N ``ContinuousBatcher`` replicas
    (module docstring: dispatch policy, breaker, migration).

    ``route`` is the batch surface mirroring ``serve_detailed``: one
    ``RequestResult`` per request, in order, never raising — now with
    ``migrated`` / ``replica`` metadata filled in. Each round the
    partitioner assigns every unfinished request to a healthy replica,
    one worker thread per replica runs ``serve_detailed`` under
    ``flight.replica_tag(i)``, and device-lost sessions re-enter the
    next round on a different replica.

    Replicas must NOT be shared with concurrent callers: the router
    owns their scheduler. ``route`` itself is synchronous and not
    reentrant (one in-flight call per router).

    ``heartbeat_stale_s`` (opt-in): the router re-wires each replica's
    ``on_heartbeat``/``heartbeat_s`` so beats land in router health
    state, and a mid-round replica whose beats stop for this long is
    taken over (module docstring). Leave ``None`` on cold-compile-heavy
    runs — a first-route compile pause is indistinguishable from a
    hang.
    """

    def __init__(self, replicas, *, fault_threshold: int = 1,
                 probe_budget: int = 4, probe_base_delay_s: float = 0.25,
                 jitter_seed: int = 0,
                 affinity_min_tokens: int | None = None,
                 affinity_max_extra_ticks: int | None = None,
                 heartbeat_stale_s: float | None = None,
                 max_failover_rounds: int | None = None,
                 prefill_replicas: int = 0,
                 sleep=time.sleep):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        n = len(self.replicas)
        # the fleet must agree on the KV pool dtype (ISSUE 16): a
        # handoff/migration between an int8 and a bf16 replica would
        # decline every payload (import_prefix's kv_dtype stamp), so a
        # mixed fleet silently degrades every migration to full replay
        # — refuse it at construction instead. Prefill and decode
        # tiers are both replicas here, so this covers the
        # disagg-prefill seam too.
        dts = {getattr(r, "kv_dtype", "bf16") for r in self.replicas}
        if len(dts) > 1:
            raise ValueError(
                f"all replicas must share one kv_dtype, got {sorted(dts)}")
        self.kv_dtype = next(iter(dts))
        # disaggregated prefill: replicas [0, prefill_replicas) form the
        # prefill tier — sessions placed there always migrate to a
        # decode replica right after their prompt finishes prefilling,
        # carrying the finished KV blocks as a host-tier handoff
        # (export_prefix -> import_prefix) instead of a token replay.
        # At least one decode replica must remain.
        if not 0 <= prefill_replicas < n:
            raise ValueError(f"prefill_replicas must be in [0, {n}), got "
                             f"{prefill_replicas}")
        self.prefill_replicas = prefill_replicas
        self._prefill_set = frozenset(range(prefill_replicas))
        self.fault_threshold = fault_threshold
        self.probe_budget = probe_budget
        self.probe_base_delay_s = probe_base_delay_s
        self.jitter_seed = jitter_seed
        self.heartbeat_stale_s = heartbeat_stale_s
        self.max_failover_rounds = (max_failover_rounds
                                    if max_failover_rounds is not None else n)
        # affinity knobs: a match shorter than one block can't skip any
        # prefill; a warm replica more than ~one full row of ticks ahead
        # of the least-loaded loses the request (module docstring).
        # t_max stays the right ceiling even though load is accumulated
        # in width-weighted tick equivalents (ISSUE 19) — those only
        # ever price a tick at or below its full-width cost
        self.affinity_min_tokens = (affinity_min_tokens
                                    if affinity_min_tokens is not None
                                    else self.replicas[0].bt)
        self.affinity_max_extra_ticks = (
            affinity_max_extra_ticks if affinity_max_extra_ticks is not None
            else self.replicas[0].t_max)
        self._sleep = sleep
        self._breakers = [CircuitBreaker(
            fault_threshold=fault_threshold, probe_budget=probe_budget,
            probe_base_delay_s=probe_base_delay_s,
            jitter_seed=jitter_seed + i) for i in range(n)]
        self._busy = [False] * n      # a worker (possibly zombie) holds it
        # per-replica retirement latch (ISSUE 20): flipping it mid-round
        # drains that one replica (its serve_detailed sees `preempted`)
        # without touching the cluster drain; the round classifier
        # migrates its cut sessions to survivors
        self._retiring = [False] * n
        self._last_beat: list[float | None] = [None] * n
        self._last_snap: list[dict | None] = [None] * n
        self._threads: list[threading.Thread] = []
        self.routed_per_replica = [0] * n
        self.stats = {"routed": 0, "affinity_routed": 0, "rounds": 0,
                      "failovers": 0, "migrations": 0, "full_replays": 0,
                      "failover_sheds": 0, "takeovers": 0, "probes": 0,
                      "probe_successes": 0, "unplaceable": 0,
                      "prefill_hops": 0, "handoffs": 0,
                      "handoff_fallbacks": 0,
                      # journal recovery at the router layer (ISSUE 15):
                      # sessions resumed from a previous process's log,
                      # completions returned without device work, and
                      # the emitted tokens re-entered as replay prefix
                      "journal_recovered": 0, "journal_deduped": 0,
                      "journal_replay_tokens": 0,
                      # elastic membership (ISSUE 20): replicas retired
                      # from / added to the fleet, and sessions a
                      # retirement drain migrated to survivors (these
                      # also count under "migrations")
                      "retired": 0, "added": 0, "retire_migrations": 0}
        for i, rep in enumerate(self.replicas):
            self._wire_heartbeat(i, rep)

    # ---- health ------------------------------------------------------------

    def _wire_heartbeat(self, i: int, rep) -> None:
        prev = rep.on_heartbeat

        def beat(snap, _i=i, _prev=prev):
            self._last_beat[_i] = time.monotonic()
            self._last_snap[_i] = snap
            if _prev is not None:
                _prev(snap)

        rep.on_heartbeat = beat
        if self.heartbeat_stale_s is not None:
            want = max(0.05, self.heartbeat_stale_s / 4)
            if rep.heartbeat_s is None or rep.heartbeat_s > want:
                rep.heartbeat_s = want

    def breaker_states(self) -> list[str]:
        return [b.state for b in self._breakers]

    def healthy_replicas(self) -> list[int]:
        return [i for i, b in enumerate(self._breakers)
                if b.healthy and not self._busy[i]]

    def active_replicas(self) -> list[int]:
        """Fleet members in ANY state but RETIRED — the set the elastic
        controller sizes, walks, and replaces over. (Healthy is a
        dispatch property; active is a membership property.)"""
        return [i for i, b in enumerate(self._breakers)
                if b.state != RETIRED]

    # ---- membership (ISSUE 20) ---------------------------------------------

    def retire_replica(self, i: int) -> None:
        """Remove replica ``i`` from the fleet: no new placements, no
        probes, and if a round is in flight its worker drains NOW (the
        per-replica latch reads as ``preempted`` inside that replica's
        ``serve_detailed`` only) — in-flight rows finish, queued work
        sheds, and the round classifier re-enters every cut session on
        the survivors, token-identically (``_sub_request``'s
        continuation path: a retirement is a PLANNED failover).
        Retirement is terminal for the probe machinery — an operator
        ``probe_replica`` cannot revive a replaced member (the race the
        unit tests pin); only :meth:`readmit_replica`, the explicit
        re-admission step of the controller's upgrade walk, returns a
        retired replica to dispatch. Idempotent. Indices are stable:
        the slot is never reused, its lists just go quiet."""
        b = self._breakers[i]
        if b.state == RETIRED:
            return
        self._retiring[i] = True
        b.state = RETIRED
        b.retry_at = None
        self.stats["retired"] += 1
        instant("replica_retired", replica=i)
        flight.record("replica_retired", replica=i,
                      busy=self._busy[i])

    def readmit_replica(self, i: int) -> None:
        """Return a RETIRED replica to dispatch (the upgrade walk's
        re-admission: sessions were drained off, weights reloaded, and
        the replica is warm again). No-op unless retired."""
        b = self._breakers[i]
        if b.state != RETIRED:
            return
        self._retiring[i] = False
        b.state = CLOSED
        b.consecutive = 0
        b._k = 0
        b.retry_at = None
        instant("replica_readmitted", replica=i)
        flight.record("replica_readmitted", replica=i)

    def add_replica(self, rep, *, prefill: bool = False) -> int:
        """Grow the fleet by one warm replica (scale-up, or the
        replacement for a retired/DEAD member) and return its index.
        The new member enters with a CLOSED breaker and receives
        traffic from the next placement on. Same-``kv_dtype`` is
        enforced exactly as at construction. Append order matters: the
        breaker lands LAST because ``healthy_replicas``/``_partition``
        enumerate ``self._breakers`` — every parallel per-index list
        must already hold index ``i`` when it becomes visible."""
        if getattr(rep, "kv_dtype", "bf16") != self.kv_dtype:
            raise ValueError(
                f"all replicas must share one kv_dtype, got "
                f"{getattr(rep, 'kv_dtype', 'bf16')!r} vs "
                f"{self.kv_dtype!r}")
        i = len(self.replicas)
        self.replicas.append(rep)
        self._busy.append(False)
        self._retiring.append(False)
        self._last_beat.append(None)
        self._last_snap.append(None)
        self.routed_per_replica.append(0)
        self._wire_heartbeat(i, rep)
        if prefill:
            self._prefill_set = frozenset(self._prefill_set | {i})
        self._breakers.append(CircuitBreaker(
            fault_threshold=self.fault_threshold,
            probe_budget=self.probe_budget,
            probe_base_delay_s=self.probe_base_delay_s,
            jitter_seed=self.jitter_seed + i))
        self.stats["added"] += 1
        instant("replica_added", replica=i, prefill=prefill)
        flight.record("replica_added", replica=i, prefill=prefill)
        return i

    def stats_snapshot(self) -> dict:
        """Router counters + per-replica breaker/health/engine state —
        the cluster-level extension of the per-batcher snapshot."""
        now = time.monotonic()
        return {
            "router": dict(self.stats),
            "routed_per_replica": list(self.routed_per_replica),
            "replicas": [{
                "breaker": b.state,
                "consecutive_faults": b.consecutive,
                "breaker_trips": b.trips,
                "busy": self._busy[i],
                "heartbeat_age_s": (None if self._last_beat[i] is None
                                    else now - self._last_beat[i]),
                "engine": self._last_snap[i],
            } for i, b in enumerate(self._breakers)],
        }

    def join_stragglers(self, timeout: float | None = None) -> None:
        """Join worker threads left behind by takeovers (tests call
        this so a zombie can't race the next route)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for t in self._threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        self._threads = [t for t in self._threads if t.is_alive()]

    # ---- probes ------------------------------------------------------------

    def _canary_request(self) -> Request:
        # single-token greedy probe: head (tokens[:-1]) is empty, so a
        # canary never pollutes the radix cache it is probing
        return Request(tokens=[0], max_new=1)

    def _canary_once(self, i: int) -> None:
        res = self.replicas[i].serve_detailed([self._canary_request()])
        if not res[0].ok:
            raise RuntimeError(res[0].error or res[0].status)

    def _auto_probe(self, now: float) -> None:
        """One canary per OPEN replica whose backoff delay has elapsed —
        the half-open state machine the partitioner consults."""
        for i, b in enumerate(self._breakers):
            if not b.probe_due(now) or self._busy[i]:
                continue
            b.begin_probe()
            self.stats["probes"] += 1
            try:
                self._canary_once(i)
            except Exception as e:   # noqa: BLE001 — any fault re-opens
                flight.record("replica_probe", replica=i, ok=False,
                              error=f"{type(e).__name__}: {e}")
                b.record_fault(time.monotonic())
                continue
            flight.record("replica_probe", replica=i, ok=True)
            self.stats["probe_successes"] += 1
            b.record_ok()

    def probe_replica(self, i: int) -> bool:
        """Blocking operator probe: drive up to ``probe_budget`` canary
        attempts through ``elastic.retry_with_backoff`` (deterministic
        schedule, per-replica jitter seed). Success re-closes the
        breaker — including a DEAD one, which auto-probing never
        revives; failure records a fault and returns False. A RETIRED
        replica always returns False without a canary: it was removed
        on purpose (likely already replaced), so reviving it would
        double capacity behind the controller's back — membership
        changes go through retire/add/readmit, not probes."""
        if self._busy[i] or self._breakers[i].state == RETIRED:
            return False
        self.stats["probes"] += 1
        try:
            retry_with_backoff(
                lambda: self._canary_once(i), budget=self.probe_budget,
                base_delay=self.probe_base_delay_s,
                jitter_seed=self.jitter_seed + i, sleep=self._sleep)
        except Exception as e:   # noqa: BLE001 — budget exhausted
            flight.record("replica_probe", replica=i, ok=False,
                          error=f"{type(e).__name__}: {e}")
            self._breakers[i].record_fault(time.monotonic())
            return False
        flight.record("replica_probe", replica=i, ok=True)
        self.stats["probe_successes"] += 1
        self._breakers[i].record_ok()
        return True

    # ---- dispatch policy ---------------------------------------------------

    def _tpot_scale(self, i: int) -> float:
        """Observed mean TPOT from the replica's last snapshot, as a
        relative speed weight (1.0 with no signal yet) — a straggler
        replica's backlog costs proportionally more."""
        snap = self._last_snap[i] or {}
        try:
            tpot = snap["slo"]["tpot_s"]
            if tpot.get("count", 0) > 0 and tpot.get("mean"):
                return max(tpot["mean"], 1e-9)
        except (KeyError, TypeError):
            pass
        return 1.0

    def _partition(self, order: list[int], sessions: list[_Session]
                   ) -> dict[int, list[int]] | None:
        """Assign every request in ``order`` to a healthy replica:
        radix-affinity first, yielding to least-loaded when the warm
        replica is too far ahead (module docstring). Returns
        ``{replica: [request indices]}`` or None when no replica is
        placeable."""
        healthy = self.healthy_replicas()
        if not healthy:
            return None
        # tier split: prefill-phase sessions go to healthy prefill
        # replicas, everything else to the decode tier; either tier
        # empty degrades to the full healthy set (unified behaviour)
        h_pre = [i for i in healthy if i in self._prefill_set]
        h_dec = [i for i in healthy if i not in self._prefill_set]
        load = {i: 0.0 for i in healthy}    # assigned ticks this round
        scale = {i: self._tpot_scale(i) for i in healthy}
        out: dict[int, list[int]] = {}
        for j in order:
            sess = sessions[j]
            cand = (h_pre if sess.phase == "prefill" and h_pre
                    else (h_dec or healthy))
            cont = list(sess.req.tokens) + list(sess.tokens)
            remaining = max(1, sess.req.max_new - len(sess.tokens))
            best_aff, aff_len = None, 0
            for i in cand:
                m = self.replicas[i].prefix_match_len(cont)
                if m > aff_len:
                    best_aff, aff_len = i, m
            least = min(cand, key=lambda i: (load[i] * scale[i], i))
            target = least
            if (best_aff is not None
                    and aff_len >= self.affinity_min_tokens
                    and load[best_aff] - load[least]
                    <= self.affinity_max_extra_ticks):
                target = best_aff
                self.stats["affinity_routed"] += 1
            rep = self.replicas[target]
            suffix = max(0, len(cont) - 1
                         - (aff_len if target == best_aff else 0))
            # load_estimate, not _rounded_need: a speculating replica's
            # decode cost is verify dispatches (k+1 ticks each) scaled
            # by its measured acceptance rate, not segment-rounded ticks.
            # prefill_cost, not raw suffix length: a chunking replica
            # pays ceil(suffix/chunk) admission waves, not one wave per
            # token — raw tokens would systematically overprice
            # long-prompt placements there (unchunked returns suffix
            # unchanged). Both estimates come back in FULL-WIDTH tick
            # equivalents: each replica weights its tick count by its
            # CURRENT width-bucket rung over the full horizon
            # (ContinuousBatcher._width_fraction, ISSUE 19), so a
            # replica serving short sessions — whose per-tick KV gather
            # is a fraction of t_max — undercuts one already stretched
            # wide by a long session, and the mixed fleet stops pricing
            # every tick as if it gathered the horizon
            load[target] += rep.prefill_cost(suffix) \
                + rep.load_estimate(remaining)
            out.setdefault(target, []).append(j)
            self.routed_per_replica[target] += 1
        return out

    def _sub_request(self, sess: _Session, rep, now: float) -> Request:
        """The Request actually submitted to ``rep`` for this session's
        next placement. First placement submits the original verbatim;
        a migration submits the token-identical continuation (or full
        replay when the continuation outgrows the replica's prompt
        window), with the REMAINING wall budget as its deadline."""
        base = sess.req
        if sess.rounds == 0 and not sess.tokens:
            return base
        cont = list(base.tokens) + list(sess.tokens)
        remaining = base.max_new - len(sess.tokens)
        if sess.tokens and (len(cont) > rep.Tb or remaining < 1):
            # prompt + partial no longer fits this replica's prompt
            # window: discard the partial and replay from the original
            # prompt — same seed, same stream, just recomputed
            self.stats["full_replays"] += 1
            sess.tokens = []
            cont = list(base.tokens)
            remaining = base.max_new
        deadline = None
        if sess.deadline_at is not None:
            deadline = max(1e-3, sess.deadline_at - now)
        return replace(base, tokens=cont, max_new=remaining,
                       deadline_s=deadline,
                       arrival_s=max(0.0, sess.arrive_abs - now))

    # ---- the routing loop --------------------------------------------------

    def route(self, requests: list[Request], *, drain=None,
              drain_deadline_s: float | None = None,
              chaos: dict | None = None,
              recovery=None) -> list[RequestResult]:
        """Serve ``requests`` across the replica set; one
        :class:`RequestResult` per request, in order, never raising.
        ``drain`` is the cluster-wide SIGTERM latch (shared with every
        replica); ``chaos`` maps replica index -> ``ChaosInjector`` for
        drills.

        ``recovery`` — a ``serve_journal.RecoveryManifest`` from a
        previous process's journal: journal-completed requests dedup
        by id (recorded stream, zero device work), journal-incomplete
        ones enter round 0 with their emitted tokens as session state,
        so the normal migration machinery replays them token-
        identically (``_sub_request``'s continuation path — a recovery
        IS a migration whose source replica was the dead process)."""
        t0 = time.monotonic()
        n = len(requests)
        results: list[RequestResult | None] = [None] * n
        rec_sessions = getattr(recovery, "sessions", None) or {}
        sessions: list[_Session] = []
        for j, r in enumerate(requests):
            # materialise identity AND the single-batcher seed default
            # (seed = index in the call) up front, so partitioning,
            # migration and journal replay can never change a stream
            rid = getattr(r, "request_id", None) or f"req-{j}"
            if r.temperature > 0 and r.seed is None:
                r = replace(r, seed=j, request_id=rid)
            elif r.request_id != rid:
                r = replace(r, request_id=rid)
            rsess = rec_sessions.get(rid)
            if (rsess is not None and not rsess.completed
                    and getattr(rsess, "seed", None) is not None
                    and r.seed != rsess.seed):
                # the journaled admission seed is the stream's truth
                r = replace(r, seed=rsess.seed)
            sess = _Session(
                req=r, arrive_abs=t0 + getattr(r, "arrival_s", 0.0),
                deadline_at=(t0 + r.deadline_s
                             if r.deadline_s is not None else None),
                # single-token prompts have nothing to prefill; a
                # max_new=1 request finishes inside its prefill hop
                # anyway, so skipping the tier saves it a migration
                phase=("prefill" if self._prefill_set
                       and len(r.tokens) > 1 and r.max_new > 1
                       else "decode"))
            if rsess is not None and rsess.prompt is not None:
                if rsess.completed:
                    # exactly-once emission across the crash
                    self.stats["journal_deduped"] += 1
                    results[j] = RequestResult(
                        status=rsess.status,
                        tokens=list(rsess.emitted), error=rsess.error,
                        request_id=rid)
                elif rsess.emitted:
                    emitted = [int(t) for t in rsess.emitted]
                    self.stats["journal_recovered"] += 1
                    self.stats["journal_replay_tokens"] += len(emitted)
                    instant("journal_session_replay", request_id=rid,
                            emitted=len(emitted))
                    if len(emitted) >= r.max_new:
                        # budget already filled on disk — the crash hit
                        # between the last delta and the end frame
                        results[j] = RequestResult(
                            status=OK, tokens=emitted[:r.max_new],
                            request_id=rid)
                    else:
                        sess.tokens = emitted
                        sess.recoveries = 1
                        sess.phase = "decode"
            sessions.append(sess)
        self.stats["routed"] += n

        def finalize(j: int, i: int | None, r: RequestResult,
                     now: float) -> None:
            if results[j] is not None:
                return                      # first terminal event wins
            sess = sessions[j]
            if sess.migrated == 0 and not sess.tokens:
                results[j] = replace(r, replica=i,   # untouched fast path
                                     request_id=sess.req.request_id)
                return
            tokens = list(sess.tokens) + list(r.tokens)
            latency = max(0.0, now - sess.arrive_abs)
            ttft = sess.ttft_s
            tpot = ((latency - ttft) / (len(tokens) - 1)
                    if ttft is not None and len(tokens) > 1 else None)
            results[j] = RequestResult(
                status=r.status, tokens=tokens, error=r.error,
                ticks=sess.ticks + r.ticks, latency_s=latency,
                recoveries=sess.recoveries + r.recoveries,
                cached_prefix_tokens=sess.cached_prefix
                + r.cached_prefix_tokens,
                queue_wait_s=sess.queue_wait_s, ttft_s=ttft, tpot_s=tpot,
                migrated=sess.migrated, replica=i,
                request_id=sess.req.request_id)

        def shed_for(j: int, why: str, now: float,
                     drain_cut: bool = False) -> None:
            sess = sessions[j]
            if sess.tokens:
                status = CANCELLED if drain_cut else TIMEOUT
            else:
                status = SHED
            finalize(j, None, RequestResult(status=status, error=why), now)

        pending = [j for j in range(n) if results[j] is None]
        rounds = 0
        while pending:
            now = time.monotonic()
            if drain is not None and getattr(drain, "preempted", False):
                # cluster is stopping: never re-place work after drain
                for j in pending:
                    shed_for(j, "shed: cluster drain", now, drain_cut=True)
                break
            self._auto_probe(now)
            placement = self._partition(pending, sessions)
            if placement is None:
                msg = (f"no healthy replica "
                       f"({self.breaker_states().count(CLOSED)} of "
                       f"{len(self.replicas)} closed)")
                self.stats["unplaceable"] += len(pending)
                for j in pending:
                    # finalize merges sessions[j].tokens in — partial
                    # streams from the dead placement are never lost
                    finalize(j, None,
                             RequestResult(status=FAILED, error=msg), now)
                break
            if rounds > self.max_failover_rounds:
                for j in pending:
                    finalize(j, None, RequestResult(
                        status=FAILED,
                        error=f"failover round budget exhausted "
                              f"({self.max_failover_rounds})"), now)
                break
            pending = self._run_round(placement, sessions, finalize,
                                      shed_for, t0, drain,
                                      drain_deadline_s, chaos or {})
            rounds += 1
            self.stats["rounds"] += 1
        for j in range(n):
            if results[j] is None:      # defensive: never return holes
                finalize(j, None, RequestResult(
                    status=FAILED, error="not routed (router bug)"),
                    time.monotonic())
        return results

    def _run_round(self, placement, sessions, finalize, shed_for, t0,
                   drain, drain_deadline_s, chaos) -> list[int]:
        """Dispatch one placement round (one worker thread per replica,
        each under its ``flight.replica_tag``), classify the results,
        and return the request indices that must re-enter the next
        round (device-lost / taken-over sessions within deadline)."""
        now = time.monotonic()
        outs: dict[int, list] = {}
        errs: dict[int, BaseException] = {}
        threads: dict[int, threading.Thread] = {}
        hops: dict[int, set[int]] = {}
        # retirement state CAPTURED by each worker as it exits: an
        # upgrade thread gating on `not _busy[i]` may readmit (clear
        # the latch) before this round's classification runs, and the
        # replica's shed sessions must still migrate, not finalise
        retired_at_exit: dict[int, bool] = {}
        round_start = now
        for i, idxs in placement.items():
            subs = []
            for j in idxs:
                sub = self._sub_request(sessions[j], self.replicas[i], now)
                if i in self._prefill_set \
                        and sessions[j].phase == "prefill":
                    # prefill-tier placement: run the prompt's prefill
                    # plus ONE decode tick (the token TTFT measures),
                    # then hop the session to the decode tier
                    sub = replace(sub, max_new=1)
                    hops.setdefault(i, set()).add(j)
                subs.append(sub)
            for j in idxs:
                sessions[j].rounds += 1

            def work(_i=i, _subs=subs):
                with flight.replica_tag(_i):
                    try:
                        outs[_i] = self.replicas[_i].serve_detailed(
                            _subs, drain=_ReplicaDrain(self, _i, drain),
                            drain_deadline_s=drain_deadline_s,
                            chaos=chaos.get(_i))
                    except BaseException as e:  # noqa: BLE001
                        errs[_i] = e
                    finally:
                        retired_at_exit[_i] = self._retiring[_i]
                        self._busy[_i] = False

            self._busy[i] = True
            t = threading.Thread(target=work, daemon=True,
                                 name=f"dcp-router-replica{i}")
            threads[i] = t
            self._threads.append(t)
            t.start()

        taken: set[int] = set()
        while True:
            live = {i: t for i, t in threads.items()
                    if i not in taken and t.is_alive()}
            if not live:
                break
            for i, t in live.items():
                t.join(0.02)
                if not t.is_alive() or self.heartbeat_stale_s is None:
                    continue
                beat = self._last_beat[i]
                ref = beat if (beat is not None and beat > round_start) \
                    else round_start
                if time.monotonic() - ref > self.heartbeat_stale_s:
                    # scheduler thread stopped beating and has no
                    # watchdog of its own: declare the replica dead and
                    # take its whole assignment; whatever the zombie
                    # eventually returns is discarded (_busy stays held
                    # until its thread actually exits)
                    taken.add(i)
                    self.stats["takeovers"] += 1

        next_pending: list[int] = []
        # SLO offsets for migrated sessions: a sub-call measures
        # queue-wait/TTFT from ITS OWN start, so shift by the round's
        # offset from the route call (≈0 for round 0)
        slo_base = round_start - t0
        for i, idxs in placement.items():
            now = time.monotonic()
            if i in taken or i in errs:
                why = (f"heartbeat stale > {self.heartbeat_stale_s}s"
                       if i in taken else
                       f"{type(errs[i]).__name__}: {errs[i]}")
                self._fail_over(i, idxs, [], sessions, why, now, slo_base,
                                shed_for, next_pending)
                continue
            res = outs.get(i, [])
            hop = hops.get(i, set())
            # retirement drain (ISSUE 20): the per-replica latch cut
            # this replica's round short. Its SHED/CANCELLED results
            # are not failures — they are the planned half of a
            # migration, so they re-enter the next round on survivors
            # with their partial streams banked (unless the CLUSTER is
            # draining too, in which case finalising wins: nobody will
            # serve them anyway)
            retiring = (retired_at_exit.get(i, self._retiring[i])
                        and not (drain is not None
                                 and getattr(drain, "preempted", False)))
            faulted: list[tuple[int, RequestResult]] = []
            for j, r in zip(idxs, res):
                if (r.status == FAILED and r.error
                        and DEVICE_LOST_MARKER in r.error):
                    faulted.append((j, r))
                    continue
                sess = sessions[j]
                if sess.queue_wait_s is None and r.queue_wait_s is not None:
                    sess.queue_wait_s = slo_base + r.queue_wait_s
                if sess.ttft_s is None and r.ttft_s is not None:
                    sess.ttft_s = slo_base + r.ttft_s
                if (retiring and r.status in (SHED, CANCELLED)
                        and not (sess.deadline_at is not None
                                 and now >= sess.deadline_at)):
                    sess.tokens.extend(r.tokens)
                    sess.ticks += r.ticks
                    sess.recoveries += r.recoveries
                    sess.cached_prefix += r.cached_prefix_tokens
                    sess.migrated += 1
                    self.stats["migrations"] += 1
                    self.stats["retire_migrations"] += 1
                    next_pending.append(j)
                    continue
                eos = self.replicas[i].eos_id
                if (j in hop and r.status == OK
                        and len(sess.tokens) + len(r.tokens)
                        < sess.req.max_new
                        and not (eos is not None and r.tokens
                                 and r.tokens[-1] == eos)):
                    # prompt prefilled, first token out, budget left:
                    # hop to the decode tier carrying the finished KV
                    # blocks (a planned move — not a migration)
                    sess.tokens.extend(r.tokens)
                    sess.ticks += r.ticks
                    sess.recoveries += r.recoveries
                    sess.cached_prefix += r.cached_prefix_tokens
                    sess.phase = "decode"
                    self.stats["prefill_hops"] += 1
                    self._handoff(i, sess)
                    next_pending.append(j)
                    continue
                finalize(j, i, r, now)
            if faulted:
                self._fail_over(i, [j for j, _ in faulted],
                                faulted, sessions,
                                faulted[0][1].error, now, slo_base,
                                shed_for, next_pending)
            elif res:
                self._breakers[i].record_ok()
        return next_pending

    def _fail_over(self, i: int, idxs: list[int], faulted, sessions,
                   why: str, now: float, slo_base: float, shed_for,
                   next_pending) -> None:
        """Replica ``i`` is gone mid-round: record the fault, open its
        breaker, accumulate the partial streams the dead replica
        reported, and queue every in-deadline session for migration —
        dumping a flight artifact that names the dead replica and the
        migrated sessions."""
        self.stats["failovers"] += 1
        self._breakers[i].record_fault(now)
        partials = dict(faulted)
        migrated: list[int] = []
        for j in idxs:
            sess = sessions[j]
            r = partials.get(j)
            if r is not None:
                # the dead replica's partial stream is host-known and
                # exact — migration continues from it
                if sess.ttft_s is None and r.ttft_s is not None:
                    sess.ttft_s = slo_base + r.ttft_s
                sess.tokens.extend(r.tokens)
                sess.ticks += r.ticks
                sess.recoveries += r.recoveries
                sess.cached_prefix += r.cached_prefix_tokens
            if sess.deadline_at is not None and now >= sess.deadline_at:
                self.stats["failover_sheds"] += 1
                shed_for(j, f"deadline expired during failover of "
                            f"replica {i}", now)
                continue
            sess.migrated += 1
            self.stats["migrations"] += 1
            migrated.append(j)
            next_pending.append(j)
        flight.record("replica_failover", replica=i, error=why,
                      sessions=migrated)
        flight.dump_on_fault("replica_failover", fault=why, replica=i,
                             migrated=migrated,
                             breaker=self._breakers[i].state)

    def _handoff(self, i: int, sess: _Session) -> None:
        """Move prefill replica ``i``'s finished KV blocks for this
        session to a decode replica: export the prompt-prefix entry
        from ``i``'s radix/tier (D2H or straight from its spill tier),
        import it into the warmest — then least-routed — healthy decode
        replica, whose own radix now holds the prefix so the next
        round's affinity probe routes the continuation there. Any miss
        (no exportable entry, CRC/shape decline, pool pressure) is a
        fallback, not an error: the decode replica simply re-prefills
        the token-identical continuation (replay)."""
        cont = list(sess.req.tokens) + list(sess.tokens)
        # version-aware dispatch (ISSUE 20): mid-rolling-upgrade the
        # fleet transiently holds two weights_versions, and a
        # cross-version import would decline anyway (the payload
        # stamp) — skip those targets up front so the export D2H is
        # never wasted; no same-version target just means replay
        src_wv = getattr(self.replicas[i], "weights_version", 0)
        targets = [t for t in self.healthy_replicas()
                   if t not in self._prefill_set
                   and getattr(self.replicas[t], "weights_version", 0)
                   == src_wv]
        ok, target = False, None
        if targets:
            target = max(targets, key=lambda t: (
                self.replicas[t].prefix_match_len(cont),
                -self.routed_per_replica[t], -t))
            try:
                payload = self.replicas[i].export_prefix(cont)
                ok = self.replicas[target].import_prefix(payload)
            except Exception:  # noqa: BLE001 — handoff is best-effort
                ok = False
        if ok:
            self.stats["handoffs"] += 1
            flight.record("prefill_handoff", src=i, dst=target,
                          n_tokens=len(cont) - 1)
        else:
            self.stats["handoff_fallbacks"] += 1
            instant("prefill_handoff_fallback", src=i, dst=target,
                    n_tokens=len(cont) - 1)
            flight.record("prefill_handoff_fallback", src=i, dst=target,
                          n_tokens=len(cont) - 1)
