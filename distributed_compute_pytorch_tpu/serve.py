"""Segment-wise continuous batching — the serving loop over the KV-cache
machinery (VERDICT r4 missing #2; the reference is training-only,
``/root/reference/main.py``).

One-shot ``infer.generate`` compiles a fixed batch to a fixed horizon:
fine for a single batch, wasteful for a STREAM of requests — short rows
finish early and their slots then burn ticks emitting garbage until the
longest row ends. This module keeps a fixed pool of ``slots`` busy
instead, with everything the TPU touches remaining static-shaped:

- **Decode segments**: one jitted ``lax.scan`` of ``segment`` ticks over
  all slots (the same per-tick math as ``infer.py`` — ``decode_step``
  per block, in-place cache writes, per-row sampling). Caches/tokens
  carry ACROSS calls as donated buffers, so consecutive segments reuse
  the same compiled program at zero re-trace cost.
- **Per-row positions**: every cache row advances an INDEPENDENT write
  position (``decode_step`` takes a ``[B]`` position vector; the Pallas
  slot write is per-row — ``ops/pallas/cache_update.py::
  kv_insert_rows_pallas`` — and decode attention masks each row at its
  own valid length). Admission writes a new prompt at the ROW'S OWN
  window ``[0, prompt_buf)`` — no global position to align to, no
  shared ``prompt_buf`` burn — and rewinds that row to slot
  ``prompt_buf - 1``. ``t_max`` is therefore a PER-REQUEST length
  bound, not a session-wide tick budget: rows recycle indefinitely on
  the same compiled programs and a session never exhausts.
- **Batched admission**: ALL pending prompts that fit free rows are
  stacked into ONE compiled multi-row prefill per admission wave (a
  ``[K, prompt_buf]`` left-padded batch scattered into the K freed
  cache rows) instead of a batch-1 call per request — k admissions cost
  one dispatch, not k. Each prompt — all tokens but its last — is
  prefilled; the LAST prompt token becomes the row's current token,
  consumed by the next segment's first tick at slot ``prompt_buf``
  exactly as standalone generation would (and keeping admission
  fetch-free — see ``_admit_impl``). Per-row ``slot_mask`` rows hide
  the pad slots; the per-row position mask hides everything the row's
  previous occupant left beyond the live position. Positions stay
  exact per family: learned-position models embed LOGICAL positions
  (0..n-1 per row), rope models rope at ABSOLUTE PER-ROW slots, and
  RoPE scores depend only on within-row slot differences, which the
  fixed window offset preserves. (The wave size ``K`` is a compiled
  shape — distinct wave sizes compile once each, bounded by ``slots``.)
- **Mesh composition**: pass ``mesh=`` (same contract as
  ``infer.make_generate_fn``) and the WHOLE serving session is sharded:
  cache rows over the batch axes (``data``/``fsdp``), KV heads over
  ``tensor`` (GQA: ``tensor`` must divide ``num_kv_heads``), expert
  FFNs over ``expert`` — the layout ``infer._CACHE_SPEC`` names, the
  same one the params trained under. The admission prefill computes at
  its own (batch-K, tensor/expert-sharded) layout and its K/V output is
  RESHARDED into the row-sharded cache layout by the scatter that
  writes the freed rows — the portable-redistribution move
  (arXiv:2112.01075): XLA inserts the collective the two layouts imply,
  and no cache is ever gathered to one device.
- **Overlapped host scheduler**: a plain queue, with the single
  device->host fetch per segment (the token harvest, ~130 ms on the
  relayed transport) OVERLAPPED with the next segment's execution:
  segment N+1 is dispatched BEFORE segment N's tokens are fetched.
  This is sound because rows are computationally independent — a row's
  tokens depend only on its own cache, never on when its neighbours
  were admitted — and budget completion is host-known (a row with
  ``remaining <= segment`` at dispatch is parked for the next segment
  without waiting for its tokens). Only eos is device-data-dependent:
  an eos'd row burns at most the one segment that was already in
  flight when the host learns of it, and those ticks are trimmed at
  harvest — served tokens are IDENTICAL to the unoverlapped schedule,
  admission simply lags one segment behind a row's (eos) completion.

**Admission fairness (the documented contract).** ``admit_policy=
"fifo"`` (default): requests are admitted strictly in arrival order —
a free row always takes the QUEUE HEAD, and no request is ever
leapfrogged by a later one. Because every row offers the same horizon
(per-row positions admit at the same window offset every time), a
request whose segment-rounded budget can never fit (``prompt_buf +
ceil(max_new/segment)*segment > t_max``) would block the head FOREVER,
so infeasibility is resolved up front: such requests are set aside,
everything else is served to completion, then :class:`HorizonError` is
raised CARRYING the completed outputs (``.outputs``) instead of
discarding finished work. ``admit_policy="skip_fit"`` opts out of the
head-of-line guarantee: each free row takes the FIRST queued request
whose rounded need fits it (today that predicate is row-independent,
so the two policies admit identical streams; skip_fit is the hook for
deployments whose rows expose heterogeneous free horizons, and it
handles never-fitting requests by skipping them in place rather than
gating up front — same terminal ``HorizonError``).

**Sampling.** Each request carries its own ``temperature`` (0 =
greedy), ``top_k``, ``top_p`` and ``seed``; the compiled segment
samples every row from its own settings and its own counter-based key
stream (``infer.sample_rows``; keys are pre-split per segment outside
the scan, the same discipline as ``infer.py`` — an in-scan split chain
costs more than the tick's math). The key for a row's t-th token
depends only on (seed, tokens-so-far), so sampled outputs are
deterministic AND invariant to ``slots``/``segment`` scheduling; a
greedy request served next to sampling requests keeps standalone
parity (``tests/test_serve.py``).

Correctness contract (``tests/test_serve.py``,
``tests/test_serve_mesh.py``): greedy-served outputs of staggered
admissions equal each prompt's standalone ``infer.generate``, token
for token, for GPT-2 (learned positions), Llama (RoPE/GQA) and the
MoE family (inference routing) — off-mesh and under data/tensor/
expert-sharded meshes (sharded serving compares against sharded
standalone generation: cross-LAYOUT equality is only a logits-
tolerance property, see ``tests/test_generate.py``). MoE capacity:
although an admission wave prefills rows over the fixed ``prompt_buf``
window, each row is its OWN routing group whose expert queue capacity
derives from that row's REAL prompt length (``moe_capacity_rows`` —
``MoEBlock.prefill_capacity``/``MoELayer.apply``), and pad tokens
claim no queue slot, so every prefilled prompt routes with exactly the
queues a standalone global-group prefill gives it even when capacity
binds. The remaining documented no-drop contract is only the LAST
prompt token: serve defers it to the first decode tick, which is
full-capacity by construction, while the standalone prefill routes it
with capacity ``C`` — the paths can disagree only if the standalone
run capacity-drops that one token (``tests/test_serve.py`` pins both
the binding-capacity parity and this boundary).

Instrumentation (the transport counters ``make bench-smoke`` asserts):
``stats`` counts segments, fetches (exactly one per segment),
overlapped fetches (the next segment was already dispatched when the
fetch was issued) and prefill calls/rows (one call per admission
wave); ``waste`` attributes every non-useful row-tick to post-eos/
budget tail, admission lag, or final drain (the serve bench's
``waste_breakdown``).
"""

from __future__ import annotations

import contextlib
import inspect
import warnings
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_compute_pytorch_tpu.core.mesh import (
    constrain, named_sharding, use_mesh)
from distributed_compute_pytorch_tpu.infer import (
    _CACHE_SPEC, _constrain_cache, sample_rows)


@dataclass
class Request:
    """One generation request: ``tokens`` (prompt ids) in, up to
    ``max_new`` continuations out (fewer if ``eos_id`` fires).

    ``temperature`` 0 (default) decodes greedily; > 0 samples, with
    optional ``top_k``/``top_p`` truncation (both require temperature
    > 0, mirroring ``infer.generate``). ``seed`` fixes the request's
    sampling stream; ``None`` defaults to the request's index in the
    ``serve()`` call, so a whole call is deterministic by default."""

    tokens: list
    max_new: int
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int | None = None


@dataclass
class _Slot:
    """Host-side bookkeeping for one cache row."""

    req_index: int = -1        # position in the request list (-1 = free)
    remaining: int = 0
    out: list = field(default_factory=list)


class HorizonError(RuntimeError):
    """A request's segment-rounded budget can never fit the per-row
    horizon (``prompt_buf + ceil(max_new/segment)*segment > t_max``).

    Raised AFTER every admissible request has been served; ``outputs``
    holds the completed results (in request order, ``[]`` for the
    rejected requests) so finished work is never discarded."""

    def __init__(self, message: str, outputs: list):
        super().__init__(message)
        self.outputs = outputs


class ContinuousBatcher:
    """Fixed-pool continuous batching for one causal LM.

    Args:
      model: any ``infer.py``-contract model (GPT-2 / Llama / MoE).
      params: its (possibly quantized) parameters — already committed
        to the mesh layout when ``mesh`` is given (restore with
        ``parallel.api.shard_pytree`` under the training strategy).
      slots: cache rows decoding concurrently (the static batch). Under
        a mesh it must divide over the batch axes
        (``data * fsdp | slots``) so every device owns whole rows.
      t_max: cache length == each ROW's length bound: one request needs
        ``prompt_buf + ceil(max_new/segment)*segment <= t_max``. Rounded
        up to the Pallas cache-window multiple (8 for bf16/f32 caches,
        32 for int8 — ``ops/pallas/cache_update.py::_window``), exactly
        as ``infer.make_generate_fn`` does: a misaligned length would
        silently drop every tick onto the ~3x-slower full-cache-copy
        ``dynamic_update_slice`` path, and the extra slots are never
        attended (the per-row position mask stops at each row's live
        position), so rounding up is observationally free.
      prompt_buf: static prompt window; prompts longer than this are
        rejected (size it to the workload's longest prompt).
      segment: ticks per compiled decode call. Smaller = finer admission
        granularity (less tail waste when a row finishes mid-segment)
        but more host round-trips; the serve bench's ``segment_sweep``
        and ``waste_breakdown`` (bench.py ``serve_long_stream``) carry
        the measured trade-off for the headline workload.
      eos_id: optional stop token (rows stop early and free their slot).
      mesh: optional ``jax.sharding.Mesh`` — SHARDED serving (module
        docstring). Batch axes shard the cache rows, ``tensor`` the KV
        heads (must divide ``num_kv_heads``), ``expert`` the expert
        FFNs; ``seq`` is rejected (decode has no sequence to shard).
      admit_policy: ``"fifo"`` (strict arrival order — the fairness
        contract in the module docstring) or ``"skip_fit"``.
    """

    def __init__(self, model, params, *, slots: int, t_max: int,
                 prompt_buf: int, segment: int = 16,
                 eos_id: int | None = None, mesh=None,
                 admit_policy: str = "fifo"):
        from distributed_compute_pytorch_tpu.ops.pallas.cache_update import (
            _pallas_ok, _window)
        if prompt_buf > t_max:
            raise ValueError(f"prompt_buf {prompt_buf} > t_max {t_max}")
        if admit_policy not in ("fifo", "skip_fit"):
            raise ValueError(f"admit_policy must be 'fifo' or 'skip_fit', "
                             f"got {admit_policy!r}")
        self.model = model
        self.params = params
        self.B = slots
        self.Tb = prompt_buf
        self.S = segment
        self.eos_id = eos_id
        self.admit_policy = admit_policy
        self._mesh = mesh
        self._block = model._block()
        # does the block rope internally (needs absolute-slot positions
        # at admission)? Llama does; GPT-2/MoE embed positions instead.
        sig = inspect.signature(self._block.apply).parameters
        self._block_takes_positions = "positions" in sig
        # MoE admission capacity (ADVICE r5): blocks whose prefill routing
        # accepts an explicit capacity get it derived from the REAL prompt
        # length, not the padded window (see _admit_impl); the per-row
        # form carries each wave row's own capacity
        self._block_takes_moe_capacity = "moe_capacity" in sig
        self._block_takes_moe_capacity_rows = "moe_capacity_rows" in sig
        hk, hd = model.kv_cache_spec()
        if mesh is not None:
            shape = dict(mesh.shape)
            tp = shape.get("tensor", 1)
            if tp > 1 and hk % tp:
                # GQA shards the NARROW cache: an indivisible kv-head dim
                # would make XLA pad-and-replicate it, silently defeating
                # the layout (same check as infer.make_generate_fn)
                raise ValueError(
                    f"tensor axis ({tp}) must divide num_kv_heads ({hk}) "
                    f"for sharded serving — the KV cache shards on kv "
                    f"heads")
            if shape.get("seq", 1) > 1:
                raise ValueError("serving does not compose with a seq>1 "
                                 "mesh axis; fold those devices into data")
            dp = shape.get("data", 1) * shape.get("fsdp", 1)
            if slots % dp:
                raise ValueError(
                    f"slots ({slots}) must divide over the batch axes "
                    f"(data*fsdp = {dp}) so every device owns whole "
                    f"cache rows")
            self._dp = dp
        else:
            self._dp = 1
        n_layers = int(jax.tree_util.tree_leaves(
            params["blocks"])[0].shape[0])
        # cache rows in the activations' dtype == the first floating
        # param leaf's (bf16 serving params -> bf16 cache; int8-quantized
        # trees surface their float scales, same outcome)
        floats = [l for l in jax.tree.leaves(params)
                  if jnp.issubdtype(l.dtype, jnp.floating)]
        dtype = floats[0].dtype if floats else jnp.float32
        # ADVICE r5: align t_max to the in-place Pallas slot write's
        # window so serving never silently falls off the fast path
        align = _window(dtype)
        self.t_max = -(-t_max // align) * align
        # per-layer KV-PAIR arrays [2(k/v), B, hk, T, hd]: each tick's
        # slot write is one window DMA per row per layer
        # (ops/pallas/cache_update.py::kv_insert_rows_pallas)
        self._n_layers = n_layers

        def dev(x, spec):
            if mesh is None:
                return x
            return jax.device_put(x, named_sharding(mesh, spec))

        self._caches = [
            {"kv": dev(jnp.zeros((2, slots, hk, self.t_max, hd), dtype),
                       _CACHE_SPEC)}
            for _ in range(n_layers)]
        if (jax.default_backend() == "tpu"
                and (mesh is not None
                     or not _pallas_ok(self._caches[0], axis=3))):
            warnings.warn(
                "serving caches fall off the Pallas window-write fast "
                "path (mesh active, multi-device, or a non-window-"
                "aligned shape): every decode tick will pay the full-"
                "cache-copy dynamic_update_slice (~3x slower measured)",
                stacklevel=2)
        row_spec = P(("data", "fsdp"))
        self._slot_mask = dev(jnp.zeros((slots, self.t_max), jnp.float32),
                              P(("data", "fsdp"), None))
        self._cur_tok = dev(jnp.zeros((slots,), jnp.int32), row_spec)
        self._n_logical = dev(jnp.zeros((slots,), jnp.int32), row_spec)
        # per-row slot of the last written token (host-tracked: admission
        # rewinds a row to Tb-1, each segment advances every row by S)
        self._row_pos = [prompt_buf - 1] * slots
        # per-row sampling settings (host-tracked, set at admission,
        # shipped with every segment dispatch — no fetch)
        self._temp = np.zeros((slots,), np.float32)
        self._topk = np.zeros((slots,), np.int32)       # 0 = off
        self._topp = np.full((slots,), 2.0, np.float32)  # >= 1 = off
        self._seed = np.zeros((slots,), np.uint32)
        self.ticks = 0             # decode ticks run this session
        self._zero_stats()
        # moe_capacity is STATIC: capacity shapes the routing one-hots, so
        # each distinct (wave size, wave-max capacity) pair compiles its
        # own admission program (bounded by slots x the same per-shape
        # compilation the standalone prefill always paid); per-row
        # capacities ride along as a traced [K] vector
        self._admit_c = jax.jit(self._admit_impl, donate_argnums=(1, 2),
                                static_argnames=("moe_capacity",))
        self._segment_c = jax.jit(self._segment_impl, donate_argnums=(1,),
                                  static_argnames=("sampling",))

    def _zero_stats(self):
        # transport counters (module docstring; asserted by the CPU
        # bench smoke): fetches == segments, every fetch with live rows
        # behind it issued AFTER the next segment's dispatch
        self.stats = {"segments": 0, "fetches": 0, "fetches_overlapped": 0,
                      "prefill_calls": 0, "prefill_rows": 0}
        # row-tick attribution for the bench's waste_breakdown: useful
        # tokens = planned_ticks - tail (tail = post-eos + budget
        # rounding); parked ticks split by whether work was waiting
        self.waste = {"planned_ticks": 0, "parked_admission_lag": 0,
                      "parked_drain": 0}

    def _mesh_ctx(self):
        return (use_mesh(self._mesh) if self._mesh is not None
                else contextlib.nullcontext())

    def reset(self):
        """Fresh session on the SAME compiled programs: zero the caches,
        masks, counters and stats and rewind every row. Lets a caller
        (the serve bench; a long-running server) run many sessions while
        paying trace+compile once — the jitted pieces are per-instance
        closures, so a new ContinuousBatcher would recompile. (With
        per-row positions rows recycle in place, so this is hygiene
        between WORKLOADS, not a horizon requirement.)"""
        self._caches = jax.tree.map(jnp.zeros_like, self._caches)
        self._slot_mask = jnp.zeros_like(self._slot_mask)
        self._cur_tok = jnp.zeros_like(self._cur_tok)
        self._n_logical = jnp.zeros_like(self._n_logical)
        self._row_pos = [self.Tb - 1] * self.B
        self._temp[:] = 0.0
        self._topk[:] = 0
        self._topp[:] = 2.0
        self._seed[:] = 0
        self.ticks = 0
        self._zero_stats()

    # ---- compiled pieces -------------------------------------------------

    def _admit_impl(self, params, caches, slot_mask, rows, prompt, pmask,
                    moe_capacity=None, moe_capacity_rows=None):
        """Prefill an admission WAVE: ``K`` requests' tokens-but-the-last
        (``prompt``/``pmask`` ``[K, prompt_buf]``, left-padded: an
        n-token head occupies slots ``prompt_buf - n .. prompt_buf - 1``)
        into cache rows ``rows [K]``, each at the row's own window
        ``[0, prompt_buf)`` — ONE compiled forward for the whole wave.

        Each request's LAST prompt token is deliberately NOT prefilled:
        the host sets it as the row's current token and the next
        segment's first tick consumes it — writing its K/V at slot
        ``prompt_buf`` and sampling the request's first new token
        exactly as a standalone ``generate`` would. This keeps admission
        a pure dispatch (no device->host read — a fetch costs ~130 ms on
        the relayed-TPU transport, which at serving admission rates
        would dominate everything; the only fetch in the serve loop is
        the per-segment token harvest). The window offset is STATIC
        (always 0): per-row positions removed the old
        global-position-dependent offset entirely.

        Under a mesh, the wave's K/V (``[2, K, hk, Tb, hd]``, kv heads
        pinned over ``tensor``) is scattered into the ROW-sharded cache
        — the layout change IS the scatter's resharding collective, the
        portable-redistribution move the module docstring names. The
        host pads ``K`` up to a multiple of the batch-axes product
        (pad rows carry all-zero masks and an OUT-OF-BOUNDS row index;
        ``mode="drop"`` discards their writes): an UNEVENLY
        batch-sharded prefill was observed to miscompile under
        mixed-axes meshes on this backend (wrong K/V values for a
        1-row wave on data x expert, CPU SPMD — the same partitioner
        fragility ``core.mesh.constrain_activations`` documents), and
        even partitioning keeps it on the well-trodden path.
        """
        model, Tb = self.model, self.Tb
        pad_count = Tb - jnp.sum(pmask.astype(jnp.int32), axis=1)
        logical = jnp.maximum(jnp.arange(Tb)[None, :] - pad_count[:, None],
                              0)
        x = constrain(model.embed(params, prompt, logical),
                      P(("data", "fsdp"), None, None))
        blocks = params["blocks"]
        kvs = []
        for i in range(self._n_layers):
            p_i = jax.tree.map(lambda a: a[i], blocks)
            sink: list = []
            kw = {"kv_sink": sink, "kv_mask": pmask}
            if self._block_takes_positions:
                kw["positions"] = jnp.arange(Tb)   # absolute slots 0..Tb-1
            if self._block_takes_moe_capacity and moe_capacity is not None:
                # expert queues sized for each row's REAL token count:
                # pads route nowhere (kv_mask) and every row is its own
                # routing group (models/moe.py), so the real tokens see
                # exactly the standalone prefill's capacity instead of
                # the window's
                kw["moe_capacity"] = moe_capacity
                if (self._block_takes_moe_capacity_rows
                        and moe_capacity_rows is not None):
                    kw["moe_capacity_rows"] = moe_capacity_rows
            x = self._block.apply(p_i, x, **kw)
            if isinstance(x, tuple):   # MoE blocks return (x, aux)
                x = x[0]
            (k, v), = sink             # [K, hk, Tb, hd]
            kvs.append((k, v))
        new_caches = []
        for c, (k, v) in zip(caches, kvs):
            kv = constrain(jnp.stack([k, v]).astype(c["kv"].dtype),
                           P(None, None, "tensor", None, None))
            new_caches.append(
                {"kv": c["kv"].at[:, rows, :, :Tb, :].set(kv,
                                                          mode="drop")})
        # each row's slot validity: the prompt mask inside the window,
        # open for decode after it — overwriting whatever the row's
        # previous occupant left (slots beyond the live position are
        # additionally hidden by the per-row position mask)
        m = jnp.concatenate(
            [pmask.astype(jnp.float32),
             jnp.ones((pmask.shape[0], self.t_max - Tb), jnp.float32)],
            axis=1)
        slot_mask = slot_mask.at[rows].set(m, mode="drop")
        return new_caches, slot_mask

    def _segment_impl(self, params, caches, slot_mask, tok, n_logical,
                      positions0, temp, top_k, top_p, seeds,
                      sampling: bool = False):
        """``S`` decode ticks for every row at its OWN position
        (``positions0 [B]`` = each row's last written slot); returns the
        [B, S] next tokens and the carried state. ``sampling`` (static)
        compiles the per-row sampling path (``infer.sample_rows``) in;
        greedy-only sessions keep the bare argmax program. Per-tick keys
        are PRE-SPLIT outside the scan (one vectorised threefry per
        segment — the in-scan split chain costs more than the tick's
        math, ``infer.py``), keyed on (row seed, tokens-so-far) so
        sampled streams are scheduling-invariant."""
        model = self.model
        blocks = params["blocks"]
        if sampling:
            base = jax.vmap(jax.random.key)(seeds)
            keys = jax.vmap(lambda k, n0: jax.vmap(
                lambda i: jax.random.fold_in(k, n0 + i))(
                    jnp.arange(self.S)))(base, n_logical)     # [B, S]
            tick_keys = jnp.swapaxes(keys, 0, 1)              # scan xs
        else:
            tick_keys = jnp.zeros((self.S,), jnp.uint32)      # unused xs

        def tick(carry, xs):
            i, key = xs
            tok, caches, n_log = carry
            p = positions0 + 1 + i         # [B] per-row slot being written
            x = constrain(model.embed(params, tok[:, None], n_log[:, None]),
                          P(("data", "fsdp"), None, None))
            new_caches = []
            for li in range(self._n_layers):
                p_l = jax.tree.map(lambda a: a[li], blocks)
                x, c2 = self._block.decode_step(p_l, x, caches[li], p,
                                                slot_mask=slot_mask)
                new_caches.append(_constrain_cache(c2))
            logits = model.readout(params, x)[:, -1]
            if sampling:
                nxt = sample_rows(logits, temp, top_k, top_p, key)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, new_caches, n_log + 1), nxt

        (tok, caches, n_logical), toks = lax.scan(
            tick, (tok, caches, n_logical),
            (jnp.arange(self.S), tick_keys))
        return caches, tok, n_logical, toks.transpose(1, 0)

    # ---- host scheduler --------------------------------------------------

    def _rounded_need(self, max_new: int) -> int:
        """Decode slots a request consumes past ``prompt_buf`` before its
        row is harvested and freed: the SEGMENT-ROUNDED budget (a row
        runs whole segments; eos can only shorten the output, not the
        worst-case tick count)."""
        return -(-max_new // self.S) * self.S

    def _fits(self, req: Request) -> bool:
        return self.Tb + self._rounded_need(req.max_new) <= self.t_max

    def _validate(self, requests):
        for r in requests:
            if len(r.tokens) > self.Tb:
                raise ValueError(
                    f"prompt of {len(r.tokens)} tokens exceeds "
                    f"prompt_buf={self.Tb}")
            if len(r.tokens) == 0:
                raise ValueError("empty prompt")
            if r.max_new < 1:
                raise ValueError(f"max_new must be >= 1, got {r.max_new}")
            if r.temperature < 0.0:
                raise ValueError(
                    f"temperature must be >= 0, got {r.temperature}")
            if r.temperature == 0.0 and (r.top_k is not None
                                         or r.top_p is not None):
                raise ValueError("top_k/top_p require temperature > 0 "
                                 "(temperature 0 is greedy)")
            if r.top_k is not None and r.top_k < 1:
                raise ValueError(f"top_k must be >= 1, got {r.top_k}")
            if r.top_p is not None and not 0.0 < r.top_p <= 1.0:
                raise ValueError(f"top_p must be in (0, 1], got {r.top_p}")

    def serve(self, requests: list[Request]) -> list[list[int]]:
        """Run every request through the pool; returns each request's
        generated tokens (trimmed at eos), in request order.

        Requests whose segment-rounded budget can never fit a row
        (``prompt_buf + ceil(max_new/segment)*segment > t_max``) are
        rejected: everything else is served to completion FIRST, then
        :class:`HorizonError` is raised with ``.outputs`` carrying the
        completed results. Admission order follows ``admit_policy``
        (class docstring: strict-FIFO fairness by default)."""
        self._validate(requests)
        outputs: list[list[int] | None] = [None] * len(requests)
        sampling = any(r.temperature > 0.0 for r in requests)
        if self.admit_policy == "fifo":
            # per-request horizon gate (segment-rounded): a reject here
            # is PERMANENT — per-row positions admit at the same window
            # offset every time, so what can't fit now can never fit,
            # and FIFO refuses to leapfrog, so an infeasible head would
            # block the queue forever
            rejected = [i for i, r in enumerate(requests)
                        if not self._fits(r)]
            rejected_set = set(rejected)
            queue = [i for i in range(len(requests))
                     if i not in rejected_set]
        else:
            # skip_fit: never-fitting requests are skipped in place at
            # admission time and reported at the end
            queue = list(range(len(requests)))
        table = [_Slot() for _ in range(self.B)]

        def pick_admissions(k_free: int) -> list[int]:
            take: list[int] = []
            if self.admit_policy == "fifo":
                while queue and len(take) < k_free:
                    take.append(queue.pop(0))
            else:
                i = 0
                while i < len(queue) and len(take) < k_free:
                    if self._fits(requests[queue[i]]):
                        take.append(queue.pop(i))
                    else:
                        i += 1
            return take

        def admit_wave():
            """ONE multi-row prefill for every pending request that has
            a free row (the batched admission: k admissions, 1 dispatch).
            All host->device, no fetch."""
            free = [b for b, s in enumerate(table) if s.req_index < 0]
            take = pick_admissions(len(free))
            if not take:
                return
            K = len(take)
            rows = free[:K]
            # pad the wave to a multiple of the batch-axes product: pad
            # rows are all-masked and scatter OUT OF BOUNDS (dropped) —
            # see _admit_impl's partitioner note; off-mesh _dp == 1
            Kp = -(-K // self._dp) * self._dp
            prompt = np.zeros((Kp, self.Tb), np.int32)
            pmask = np.zeros((Kp, self.Tb), np.float32)
            lasts = np.zeros((K,), np.int32)
            n_log = np.zeros((K,), np.int32)
            caps = []
            for j, ri in enumerate(take):
                req = requests[ri]
                # prefill all but the last prompt token; the next
                # segment's first tick consumes that one (_admit_impl)
                head, lasts[j] = req.tokens[:-1], req.tokens[-1]
                n = len(head)
                n_log[j] = n
                if n:
                    prompt[j, self.Tb - n:] = head
                    pmask[j, self.Tb - n:] = 1.0
                if self._block_takes_moe_capacity:
                    caps.append(self._block.prefill_capacity(
                        len(req.tokens)))
            kw = {}
            if caps:
                kw["moe_capacity"] = max(caps)
                if self._block_takes_moe_capacity_rows:
                    kw["moe_capacity_rows"] = jnp.asarray(
                        caps + [1] * (Kp - K), jnp.int32)
            rows_j = jnp.asarray(rows, jnp.int32)
            rows_pad = jnp.asarray(rows + [self.B] * (Kp - K), jnp.int32)
            with self._mesh_ctx():
                self._caches, self._slot_mask = self._admit_c(
                    self.params, self._caches, self._slot_mask, rows_pad,
                    jnp.asarray(prompt), jnp.asarray(pmask), **kw)
                self._cur_tok = self._cur_tok.at[rows_j].set(
                    jnp.asarray(lasts))
                self._n_logical = self._n_logical.at[rows_j].set(
                    jnp.asarray(n_log))
            for j, ri in enumerate(take):
                b = rows[j]
                req = requests[ri]
                self._row_pos[b] = self.Tb - 1   # the row's own horizon
                self._temp[b] = req.temperature
                self._topk[b] = req.top_k or 0
                self._topp[b] = req.top_p if req.top_p is not None else 2.0
                self._seed[b] = np.uint32(
                    req.seed if req.seed is not None else ri)
                slot = table[b]
                slot.req_index = ri
                slot.out = []
                slot.remaining = req.max_new
            self.stats["prefill_calls"] += 1
            self.stats["prefill_rows"] += K

        def dispatch_segment():
            """Dispatch ONE compiled segment (no fetch). Returns the
            (device tokens, plan) pair the later harvest consumes, or
            None when no row has budget left to tick. Budget depletion
            is applied HERE, at dispatch — it is host-known — so the
            overlapping caller can decide about segment N+1 without
            waiting for segment N's tokens; rows that are done (or
            free) are parked at the window edge, where their garbage
            writes stay inside [Tb, Tb + S) (in range because any
            admission implies Tb + S <= t_max)."""
            plan = []
            for b, slot in enumerate(table):
                if slot.req_index >= 0 and slot.remaining > 0:
                    take = min(slot.remaining, self.S)
                    plan.append((b, slot.req_index, take,
                                 slot.remaining - take <= 0))
            if not plan:
                return None
            pending = (bool(queue) if self.admit_policy == "fifo"
                       else any(self._fits(requests[i]) for i in queue))
            active = {b for b, _, _, _ in plan}
            for b in range(self.B):
                if b not in active:
                    self._row_pos[b] = self.Tb - 1
                    key = ("parked_admission_lag" if pending
                           else "parked_drain")
                    self.waste[key] += self.S
            with self._mesh_ctx():
                (self._caches, self._cur_tok, self._n_logical, toks
                 ) = self._segment_c(
                    self.params, self._caches, self._slot_mask,
                    self._cur_tok, self._n_logical,
                    jnp.asarray(self._row_pos, jnp.int32),
                    jnp.asarray(self._temp), jnp.asarray(self._topk),
                    jnp.asarray(self._topp), jnp.asarray(self._seed),
                    sampling=sampling)
            for b in range(self.B):
                self._row_pos[b] += self.S
            self.ticks += self.S
            self.stats["segments"] += 1
            for b, ri, take, _ in plan:
                table[b].remaining -= take
                self.waste["planned_ticks"] += self.S
            return toks, plan

        def harvest(seg, overlapped: bool):
            """THE one device->host fetch per segment. ``overlapped``
            records whether the next segment was already dispatched
            (the counter the bench smoke asserts)."""
            toks, plan = seg
            self.stats["fetches"] += 1
            if overlapped:
                self.stats["fetches_overlapped"] += 1
            toks_h = np.asarray(toks)
            for b, ri, take, done_after in plan:
                if outputs[ri] is not None:
                    # the request finished (eos) in an earlier segment
                    # while this one was already in flight — its ticks
                    # are overlap tail waste, never tokens
                    continue
                slot = table[b]
                slot.out.extend(int(t) for t in toks_h[b, :take])
                done = done_after
                if self.eos_id is not None and self.eos_id in slot.out:
                    slot.out = slot.out[:slot.out.index(self.eos_id) + 1]
                    done = True
                if done:
                    outputs[ri] = slot.out
                    slot.req_index = -1
                    slot.out = []
                    slot.remaining = 0

        # ---- the overlapped loop: dispatch N+1 BEFORE fetching N ----
        admit_wave()
        seg = dispatch_segment()
        while seg is not None:
            nxt = dispatch_segment()       # overlap (None: nothing live)
            harvest(seg, overlapped=nxt is not None)
            admit_wave()                   # freed rows -> wave for N+2
            if nxt is None:
                nxt = dispatch_segment()   # revived by fresh admissions
            seg = nxt

        results = [o if o is not None else [] for o in outputs]
        if self.admit_policy != "fifo":
            rejected = [i for i in queue if outputs[i] is None]
        if rejected:
            worst = max(self._rounded_need(requests[i].max_new)
                        for i in rejected)
            raise HorizonError(
                f"per-row horizon exhausted for {len(rejected)} "
                f"request(s): prompt_buf={self.Tb} + segment-rounded "
                f"max_new (worst {worst}) exceeds t_max={self.t_max} — "
                f"raise t_max or shrink max_new (completed outputs are "
                f"on this error's .outputs)", results)
        return results
