"""Flash-decode: single-position cached attention as a Pallas kernel.

Why (measured v5e, 2026-07-30, GPT-2-small decode shapes): XLA's dense
masked attention streams the KV cache at ~45% of HBM bandwidth when the
query is a single row (12 MHA layers x [16, 12, 384, 64] bf16 read in
0.611 ms vs the 0.28 ms full-bandwidth floor), and it always reads the
FULL ``t_max`` window even though only slots ``0..pos`` are valid (67%
on the bench's average tick). This kernel fixes both:

- **Explicit DMA streaming**: K/V stay in HBM (``memory_space=ANY``);
  the kernel double-buffers block-sized chunks into VMEM scratch with
  ``make_async_copy``, so the stream runs at DMA bandwidth regardless
  of the 1-row query shape that starves XLA's tiling.
- **Dynamic length**: the block loop bound is ``pos // block_k + 1`` —
  a traced scalar (scalar-prefetched), so slots beyond ``pos`` are
  never fetched at all. XLA cannot express this with static shapes.
- **Online softmax** (the flash recipe) in f32.

**The packed-lane trick**: Mosaic only slices VMEM memrefs at 128-lane
granularity, and ``head_dim`` is 64 — so the caches are viewed (free,
contiguous reshape) as ``[B, Hk, T/2, 128]``: each row packs slot pair
``(2i, 2i+1)``. Scores come from two matmuls with half-zero queries
(``[q|0]`` hits the even slots, ``[0|q]`` the odd), and the packed V
block multiplies against the interleaved probability row — producing
``[sum p*v_even | sum p*v_odd]`` in the two lane halves, which one
final 128-lane dot against ``[I|I]`` folds back to 64. Everything is
MXU-shaped; no lane-slicing anywhere.

**Status: MEASURED AND REJECTED as the default decode path** (kept as
reference + test-covered for future hardware/compiler revisions).
Correct to bf16 round-off, but on v5e the 12-layer GPT-2-shaped read
loop measures 1.73 ms/tick vs 0.45-0.60 for XLA's dense path. Why: the
per-(batch, head) work is a 1-row GEMV against that pair's private K/V
— there is nothing to batch into the MXU's 8-sublane minimum, so the
per-head compute (not the DMA stream) dominates; a per-(b,h) grid was
6.5x slower still (192 serial DMA latencies). The dynamic-length DMA
saving (~33% of bytes on the bench's average tick) cannot pay for
~8x-underutilised compute tiles. Lesson recorded: XLA's fused masked
attention is already within ~2x of the bandwidth floor for decode, and
the remaining gap is sublane waste both implementations share.

Scope: ``slot_mask`` unsupported; even ``T``; ``hd == 64``. Numerics:
f32 scores/accumulator like the dense path; parity pinned in
``tests/test_decode_attention.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(pos_ref, q_ref, k_hbm, v_hbm, out_ref, *, block_pairs: int,
            scale: float, num_heads: int):
    b = pl.program_id(0)
    # clamp: ``pos`` is traced, so a caller off-by-one (pos == T) must
    # degrade like the dense path's mask instead of DMA-reading past the
    # cache buffer. pos_ref is per-row [B]: grid step b streams only up
    # to ITS row's valid length (scalar pos broadcasts in the wrapper).
    total_pairs = k_hbm.shape[2]
    pos = jnp.minimum(pos_ref[b], total_pairs * 2 - 1)
    # pairs-per-block loop bound: block covering slot ``pos`` included
    nb = (pos // 2) // block_pairs + 1
    G = q_ref.shape[2]
    hd = q_ref.shape[3]
    zeros = jnp.zeros((G, hd), jnp.float32)
    q_all = q_ref[0].astype(jnp.float32) * scale           # [Hk, G, hd]
    q_even = [jnp.concatenate([q_all[h], zeros], axis=1)
              for h in range(num_heads)]                   # each [G, 2hd]
    q_odd = [jnp.concatenate([zeros, q_all[h]], axis=1)
             for h in range(num_heads)]
    # lane-fold matrix [2hd, hd]: [I | I]^T — collapses the two packed
    # halves of the accumulated PV row back to head_dim lanes
    eye = jnp.eye(hd, dtype=jnp.float32)
    fold = jnp.concatenate([eye, eye], axis=0)             # [2hd, hd]

    def body(scratch_k, scratch_v, sem_k, sem_v):
        # ONE DMA per (pair-block, k/v) covers every head: [Hk, BP, 2hd]
        # chunks are ~190 KB, big enough to hit DMA bandwidth; the
        # per-head compute below runs while the next chunk streams
        def dma(slot, kb, which):
            hbm, scr, sem = ((k_hbm, scratch_k, sem_k) if which == 0
                             else (v_hbm, scratch_v, sem_v))
            return pltpu.make_async_copy(
                hbm.at[b, :, pl.ds(kb * block_pairs, block_pairs), :],
                scr.at[slot], sem.at[slot])

        dma(0, 0, 0).start()
        dma(0, 0, 1).start()

        def block_step(kb, carry):
            ms, ls, accs = carry       # each [Hk, G, 1] / [Hk, G, 2hd]
            slot = kb % 2
            nxt = (kb + 1) % 2

            @pl.when(kb + 1 < nb)
            def _():
                dma(nxt, kb + 1, 0).start()
                dma(nxt, kb + 1, 1).start()

            dma(slot, kb, 0).wait()
            dma(slot, kb, 1).wait()

            base = kb * block_pairs * 2
            new_m, new_l, new_acc = [], [], []
            for h in range(num_heads):
                kp = scratch_k[slot][h].astype(jnp.float32)  # [BP, 2hd]
                vp = scratch_v[slot][h].astype(jnp.float32)
                s_even = jax.lax.dot_general(                # [G, BP]
                    q_even[h], kp, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                s_odd = jax.lax.dot_general(
                    q_odd[h], kp, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                ids = base + 2 * lax.broadcasted_iota(jnp.int32,
                                                      s_even.shape, 1)
                s_even = jnp.where(ids <= pos, s_even, -1e30)
                s_odd = jnp.where(ids + 1 <= pos, s_odd, -1e30)

                m, l, acc = ms[h], ls[h], accs[h]
                blk_max = jnp.maximum(
                    jnp.max(s_even, axis=1, keepdims=True),
                    jnp.max(s_odd, axis=1, keepdims=True))
                m_new = jnp.maximum(m, blk_max)              # [G, 1]
                alpha = jnp.exp(m - m_new)
                p_even = jnp.exp(s_even - m_new)             # [G, BP]
                p_odd = jnp.exp(s_odd - m_new)
                l_new = (l * alpha
                         + jnp.sum(p_even, axis=1, keepdims=True)
                         + jnp.sum(p_odd, axis=1, keepdims=True))
                # vp rows pack [v_{2i} | v_{2i+1}]: p_even @ vp holds the
                # wanted sum in its LEFT lane half, p_odd @ vp in its
                # RIGHT; merge halves with a lane select
                pv_e = jax.lax.dot_general(
                    p_even, vp, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)      # [G, 2hd]
                pv_o = jax.lax.dot_general(
                    p_odd, vp, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                lane = lax.broadcasted_iota(jnp.int32, pv_e.shape, 1)
                contrib = jnp.where(lane < hd, pv_e, pv_o)
                new_m.append(m_new)
                new_l.append(l_new)
                new_acc.append(acc * alpha + contrib)
            return (tuple(new_m), tuple(new_l), tuple(new_acc))

        m0 = tuple(jnp.full((G, 1), -jnp.inf, jnp.float32)
                   for _ in range(num_heads))
        l0 = tuple(jnp.zeros((G, 1), jnp.float32)
                   for _ in range(num_heads))
        acc0 = tuple(jnp.zeros((G, 2 * hd), jnp.float32)
                     for _ in range(num_heads))
        _, ls, accs = lax.fori_loop(0, nb, block_step, (m0, l0, acc0))
        for h in range(num_heads):
            out = jax.lax.dot_general(accs[h] / ls[h], fold,
                                      (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            out_ref[0, h] = out.astype(out_ref.dtype)

    pl.run_scoped(
        body,
        scratch_k=pltpu.VMEM((2, num_heads, block_pairs, 2 * hd),
                             k_hbm.dtype),
        scratch_v=pltpu.VMEM((2, num_heads, block_pairs, 2 * hd),
                             v_hbm.dtype),
        sem_k=pltpu.SemaphoreType.DMA((2,)),
        sem_v=pltpu.SemaphoreType.DMA((2,)),
    )


def _paged_kernel(pos_ref, tbl_ref, q_ref, k_hbm, v_hbm, out_ref, *,
                  block_pairs: int, scale: float, num_heads: int,
                  nb: int):
    """Block-table variant of :func:`_kernel`: the caches are a POOL of
    fixed-size blocks ``[P, Hk, bt/2, 2hd]`` (packed-lane pair view) and
    row ``b``'s logical block ``j`` streams from physical block
    ``tbl_ref[b * nb + j]`` — the paged-attention read, where the
    per-row DMA source is a table lookup instead of a contiguous slice.
    One pool block == one DMA chunk, so the dynamic length bound
    (``pos[b] // bt + 1`` blocks) never fetches past a row's live
    prefix. Same online-softmax/packed-lane math as the dense kernel."""
    b = pl.program_id(0)
    total_pairs = block_pairs * nb
    pos = jnp.minimum(pos_ref[b], total_pairs * 2 - 1)
    nblk = (pos // 2) // block_pairs + 1
    G = q_ref.shape[2]
    hd = q_ref.shape[3]
    zeros = jnp.zeros((G, hd), jnp.float32)
    q_all = q_ref[0].astype(jnp.float32) * scale
    q_even = [jnp.concatenate([q_all[h], zeros], axis=1)
              for h in range(num_heads)]
    q_odd = [jnp.concatenate([zeros, q_all[h]], axis=1)
             for h in range(num_heads)]
    eye = jnp.eye(hd, dtype=jnp.float32)
    fold = jnp.concatenate([eye, eye], axis=0)

    def body(scratch_k, scratch_v, sem_k, sem_v):
        def dma(slot, kb, which):
            hbm, scr, sem = ((k_hbm, scratch_k, sem_k) if which == 0
                             else (v_hbm, scratch_v, sem_v))
            phys = tbl_ref[b * nb + kb]        # the table lookup
            return pltpu.make_async_copy(
                hbm.at[phys], scr.at[slot], sem.at[slot])

        dma(0, 0, 0).start()
        dma(0, 0, 1).start()

        def block_step(kb, carry):
            ms, ls, accs = carry
            slot = kb % 2
            nxt = (kb + 1) % 2

            @pl.when(kb + 1 < nblk)
            def _():
                dma(nxt, kb + 1, 0).start()
                dma(nxt, kb + 1, 1).start()

            dma(slot, kb, 0).wait()
            dma(slot, kb, 1).wait()

            base = kb * block_pairs * 2
            new_m, new_l, new_acc = [], [], []
            for h in range(num_heads):
                kp = scratch_k[slot][h].astype(jnp.float32)
                vp = scratch_v[slot][h].astype(jnp.float32)
                s_even = jax.lax.dot_general(
                    q_even[h], kp, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                s_odd = jax.lax.dot_general(
                    q_odd[h], kp, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                ids = base + 2 * lax.broadcasted_iota(jnp.int32,
                                                      s_even.shape, 1)
                s_even = jnp.where(ids <= pos, s_even, -1e30)
                s_odd = jnp.where(ids + 1 <= pos, s_odd, -1e30)

                m, l, acc = ms[h], ls[h], accs[h]
                blk_max = jnp.maximum(
                    jnp.max(s_even, axis=1, keepdims=True),
                    jnp.max(s_odd, axis=1, keepdims=True))
                m_new = jnp.maximum(m, blk_max)
                alpha = jnp.exp(m - m_new)
                p_even = jnp.exp(s_even - m_new)
                p_odd = jnp.exp(s_odd - m_new)
                l_new = (l * alpha
                         + jnp.sum(p_even, axis=1, keepdims=True)
                         + jnp.sum(p_odd, axis=1, keepdims=True))
                pv_e = jax.lax.dot_general(
                    p_even, vp, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                pv_o = jax.lax.dot_general(
                    p_odd, vp, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                lane = lax.broadcasted_iota(jnp.int32, pv_e.shape, 1)
                contrib = jnp.where(lane < hd, pv_e, pv_o)
                new_m.append(m_new)
                new_l.append(l_new)
                new_acc.append(acc * alpha + contrib)
            return (tuple(new_m), tuple(new_l), tuple(new_acc))

        m0 = tuple(jnp.full((G, 1), -jnp.inf, jnp.float32)
                   for _ in range(num_heads))
        l0 = tuple(jnp.zeros((G, 1), jnp.float32)
                   for _ in range(num_heads))
        acc0 = tuple(jnp.zeros((G, 2 * hd), jnp.float32)
                     for _ in range(num_heads))
        _, ls, accs = lax.fori_loop(0, nblk, block_step, (m0, l0, acc0))
        for h in range(num_heads):
            out = jax.lax.dot_general(accs[h] / ls[h], fold,
                                      (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            out_ref[0, h] = out.astype(out_ref.dtype)

    pl.run_scoped(
        body,
        scratch_k=pltpu.VMEM((2, num_heads, block_pairs, 2 * hd),
                             k_hbm.dtype),
        scratch_v=pltpu.VMEM((2, num_heads, block_pairs, 2 * hd),
                             v_hbm.dtype),
        sem_k=pltpu.SemaphoreType.DMA((2,)),
        sem_v=pltpu.SemaphoreType.DMA((2,)),
    )


def decode_attention_paged_pallas(q, k_pool, v_pool, tables, pos, *,
                                  scale: float | None = None):
    """Paged flash-decode: ``q [B, Hk, G, hd]`` against a BLOCK POOL
    ``k_pool/v_pool [P, Hk, bt, hd]`` addressed through ``tables
    [B, nb]`` (row ``b``'s logical slot ``t`` lives in pool block
    ``tables[b, t // bt]`` at offset ``t % bt``); attends logical slots
    ``0..pos[b]``. The pool block is the DMA unit, so the stream
    touches exactly the blocks a row's live prefix occupies — the
    block-table analogue of the dense kernel's dynamic length bound.

    Reference status, like the dense kernel above (measured-rejected as
    the default decode path on v5e): the per-(batch,head) GEMV shape
    underuses the MXU regardless of how K/V are addressed; kept
    correct + covered for future hardware/compiler revisions, and as
    the recipe for fusing the table lookup into the stream. ``hd`` must
    be 64 and ``bt`` even (the packed-lane layout)."""
    B, Hk, G, hd = q.shape
    P, _, bt, _ = k_pool.shape
    nb = tables.shape[1]
    assert hd == 64, hd
    assert bt % 2 == 0, bt
    scale = (hd ** -0.5) if scale is None else scale
    block_pairs = bt // 2
    kp = k_pool.reshape(P, Hk, bt // 2, 2 * hd)
    vp = v_pool.reshape(P, Hk, bt // 2, 2 * hd)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hk, G, hd), lambda b, p, t: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hk, G, hd), lambda b, p, t: (b, 0, 0, 0)),
    )
    pos = jnp.broadcast_to(jnp.atleast_1d(pos).astype(jnp.int32), (B,))
    return pl.pallas_call(
        functools.partial(_paged_kernel, block_pairs=block_pairs,
                          scale=scale, num_heads=Hk, nb=nb),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
    )(pos, tables.reshape(-1).astype(jnp.int32), q, kp, vp)


def decode_attention_pallas(q, k_cache, v_cache, pos, *,
                            scale: float | None = None,
                            block_k: int = 128):
    """``q [B, Hk, G, hd]`` (grouped query rows), caches
    ``[B, Hk, T, hd]``; attends slots ``0..pos``. ``pos`` is a scalar
    (every row at the same position) or an int32 ``[B]`` vector (per-row
    valid lengths — the serving loop's per-row decode positions); each
    grid step streams only its row's ``pos[b] // block_k + 1`` blocks.
    Returns ``[B, Hk, G, hd]`` in q's dtype. ``hd`` must be 64 (the
    packed-lane layout; the framework's decode models all use 64) and
    ``T`` must be divisible by ``block_k`` (cache lengths are multiples
    of 128)."""
    B, Hk, G, hd = q.shape
    T = k_cache.shape[2]
    assert hd == 64, hd
    assert T % block_k == 0 and block_k % 2 == 0, (T, block_k)
    scale = (hd ** -0.5) if scale is None else scale
    block_pairs = block_k // 2
    kp = k_cache.reshape(B, Hk, T // 2, 2 * hd)
    vp = v_cache.reshape(B, Hk, T // 2, 2 * hd)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hk, G, hd), lambda b, p: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hk, G, hd), lambda b, p: (b, 0, 0, 0)),
    )
    pos = jnp.broadcast_to(jnp.atleast_1d(pos).astype(jnp.int32), (B,))
    return pl.pallas_call(
        functools.partial(_kernel, block_pairs=block_pairs, scale=scale,
                          num_heads=Hk),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
    )(pos, q, kp, vp)
